"""ops/corr_stream.py: the streamed tiled correlation->top-K band.

The contract under test is EXACTNESS, not closeness: eagerly, the
streamed band — values AND indices — is bitwise identical to the dense
reference ``topk_band(correlation_4d(...), k, values_from=
mutual_matching(...), mutual=...)`` for mutual on and off, rectangular
grids, tiles that do and do not divide hB*wB, engineered ties, f32 and
bf16. Under jit the indices stay exactly equal and values agree to XLA
codegen ulps (the same latitude every other jit-vs-eager drill in this
repo grants). On top of that sit the integration drills: the sparse
pipeline's corr_impl dispatch is bitwise, a 3-step frozen-trunk training
run is bitwise dense-vs-stream, a serve-engine variant flip between the
two impls neither recompiles nor changes a bit of output, and the audit
programs prove the memory claim (stream highwater <= 0.35x dense).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.ops.band import topk_band
from ncnet_tpu.ops.corr_stream import corr_stream_band, resolve_corr_tile
from ncnet_tpu.ops.correlation import correlation_4d
from ncnet_tpu.ops.matching import mutual_matching


def _feats(seed, b, h, w, c, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(b, h, w, c).astype(np.float32)).astype(dtype)


def _dense_band(fa, fb, k, mutual):
    corr = correlation_4d(fa, fb)
    return topk_band(corr, k, values_from=mutual_matching(corr), mutual=mutual)


def _assert_bitwise(got, want):
    got, want = np.asarray(got), np.asarray(want)
    assert got.dtype == want.dtype and got.shape == want.shape
    view = {2: np.uint16, 4: np.uint32}[got.dtype.itemsize]
    np.testing.assert_array_equal(got.view(view), want.view(view))


# ----------------------------------------------------------------------
# eager bitwise equality vs the dense reference


@pytest.mark.parametrize("mutual", [False, True])
@pytest.mark.parametrize(
    "shape",
    [
        (2, 5, 7, 6, 9, 16),  # rectangular, hA*wA != hB*wB
        (1, 4, 4, 4, 4, 8),   # square
        (2, 3, 2, 7, 5, 12),  # nA < K cases exercised below
    ],
)
def test_stream_band_bitwise_matches_dense(shape, mutual):
    b, ha, wa, hb, wb, c = shape
    nb = hb * wb
    fa = _feats(0, b, ha, wa, c)
    fb = _feats(1, b, hb, wb, c)
    # tile 5 never divides these nb (54, 16, 35) evenly except by luck;
    # tile nb is the single-slab degenerate case
    for k in (1, 3, nb):
        for tile in (5, nb):
            want_v, want_i = _dense_band(fa, fb, k, mutual)
            got_v, got_i = corr_stream_band(
                fa, fb, k, mutual=mutual, tile=tile
            )
            np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
            _assert_bitwise(got_v, want_v)


@pytest.mark.parametrize("mutual", [False, True])
def test_stream_band_bitwise_bf16(mutual):
    fa = _feats(2, 2, 4, 5, 8, jnp.bfloat16)
    fb = _feats(3, 2, 3, 6, 8, jnp.bfloat16)
    want_v, want_i = _dense_band(fa, fb, 4, mutual)
    got_v, got_i = corr_stream_band(fa, fb, 4, mutual=mutual, tile=7)
    assert got_v.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
    _assert_bitwise(got_v, want_v)


@pytest.mark.parametrize("mutual", [False, True])
def test_stream_band_ties_bitwise(mutual):
    """Engineered exact score collisions: small-integer features make
    many dot products equal, so the merge's tie order (value desc, index
    asc — lax.top_k's) is actually load-bearing here."""
    rng = np.random.RandomState(11)
    fa = jnp.asarray(rng.randint(-1, 2, (2, 3, 4, 6)).astype(np.float32))
    fb = jnp.asarray(rng.randint(-1, 2, (2, 4, 3, 6)).astype(np.float32))
    want_v, want_i = _dense_band(fa, fb, 5, mutual)
    got_v, got_i = corr_stream_band(fa, fb, 5, mutual=mutual, tile=4)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
    _assert_bitwise(got_v, want_v)


@pytest.mark.parametrize("mutual", [False, True])
def test_stream_band_complete_band_chains_to_dense(mutual):
    """K = hB*wB runs the complete band; chained with `topk_band`'s own
    completeness contract (tests/test_band.py) this pins the streamed
    path all the way to the dense pipeline."""
    fa, fb = _feats(4, 1, 3, 3, 8), _feats(5, 1, 3, 3, 8)
    want_v, want_i = _dense_band(fa, fb, 9, mutual)
    got_v, got_i = corr_stream_band(fa, fb, 9, mutual=mutual, tile=2)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
    _assert_bitwise(got_v, want_v)


# ----------------------------------------------------------------------
# jit, gradients, validation


def test_stream_band_jit_matches_eager():
    fa, fb = _feats(6, 2, 4, 5, 16), _feats(7, 2, 5, 4, 16)
    for mutual in (False, True):
        ev, ei = corr_stream_band(fa, fb, 6, mutual=mutual, tile=7)
        jv, ji = jax.jit(
            lambda a, b, m=mutual: corr_stream_band(a, b, 6, mutual=m, tile=7)
        )(fa, fb)
        # indices are integer selections — exactly equal; values may
        # differ by XLA codegen ulps (fusion re-association), same as
        # every jit-vs-eager drill in the repo
        np.testing.assert_array_equal(np.asarray(ji), np.asarray(ei))
        np.testing.assert_allclose(
            np.asarray(jv), np.asarray(ev), rtol=1e-6, atol=1e-7
        )


@pytest.mark.parametrize("mutual", [False, True])
def test_stream_band_grad_matches_dense(mutual):
    """The gather-only custom VJP against JAX AD through the dense path.
    The dense backward scatters into the full volume and splits tied
    row/col maxima evenly; the streamed backward routes those cotangents
    to the first argmax — a measure-zero divergence absent here (random
    features), so the gradients must agree to float tolerance."""
    fa, fb = _feats(8, 1, 3, 4, 8), _feats(9, 1, 4, 3, 8)

    def stream_loss(a, b):
        v, _ = corr_stream_band(a, b, 5, mutual=mutual, tile=5)
        return jnp.sum(v * v)

    def dense_loss(a, b):
        v, _ = _dense_band(a, b, 5, mutual)
        return jnp.sum(v * v)

    gs = jax.grad(stream_loss, argnums=(0, 1))(fa, fb)
    gd = jax.grad(dense_loss, argnums=(0, 1))(fa, fb)
    for s, d in zip(gs, gd):
        np.testing.assert_allclose(
            np.asarray(s), np.asarray(d), rtol=2e-4, atol=1e-6
        )


def test_stream_band_validation():
    fa, fb = _feats(10, 1, 2, 2, 4), _feats(10, 1, 2, 2, 4)
    with pytest.raises(ValueError, match="tile"):
        resolve_corr_tile(0, 4)
    assert resolve_corr_tile(128, 4) == 4  # clamps to nb
    with pytest.raises(ValueError):
        corr_stream_band(fa, fb, 0)
    with pytest.raises(ValueError):
        corr_stream_band(fa, fb, 5)  # k > hB*wB


# ----------------------------------------------------------------------
# config dispatch: sparse pipeline, model config, train-step validation


def _cfg(**kw):
    return ImMatchNetConfig(
        feature_extraction_cnn="patch16",
        ncons_kernel_sizes=(3,),
        ncons_channels=(1,),
        **kw,
    )


def test_sparse_pipeline_stream_bitwise():
    cfg_d = _cfg(nc_topk=5)
    cfg_s = cfg_d.replace(corr_impl="stream", corr_stream_tile=6)
    params = init_immatchnet(jax.random.PRNGKey(0), cfg_d)
    from ncnet_tpu.sparse.pipeline import sparse_match_pipeline

    fa, fb = _feats(12, 2, 3, 4, 256), _feats(13, 2, 4, 3, 256)
    vd, id_, gd = sparse_match_pipeline(params["neigh_consensus"], cfg_d, fa, fb)
    vs, is_, gs = sparse_match_pipeline(params["neigh_consensus"], cfg_s, fa, fb)
    assert gd == gs
    np.testing.assert_array_equal(np.asarray(is_), np.asarray(id_))
    _assert_bitwise(vs, vd)


def test_corr_impl_validation():
    from ncnet_tpu.sparse.pipeline import resolve_corr_impl
    from ncnet_tpu.train.step import check_sparse_config

    with pytest.raises(ValueError, match="corr_impl"):
        resolve_corr_impl(_cfg(corr_impl="tiled"))
    # stream without a band path: the dense NC stack needs the volume
    with pytest.raises(ValueError, match="band path"):
        check_sparse_config(_cfg(corr_impl="stream"))
    check_sparse_config(_cfg(corr_impl="stream", nc_topk=4))  # ok
    from ncnet_tpu.models.immatchnet import match_pipeline

    cfg = _cfg(corr_impl="stream")
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    fa = _feats(14, 1, 2, 2, 256)
    with pytest.raises(ValueError, match="band path"):
        match_pipeline(params["neigh_consensus"], cfg, fa, fa)
    # legacy config dicts (no corr_impl key) default to the dense path
    d = _cfg().to_dict()
    d.pop("corr_impl"), d.pop("corr_stream_tile")
    legacy = ImMatchNetConfig.from_dict(d)
    assert resolve_corr_impl(legacy) == "dense"


def test_training_three_steps_bitwise_parity():
    """3 jitted frozen-trunk steps from cached features, dense vs stream
    configs (tile 6 does NOT divide nb=12): losses AND every updated
    param leaf bitwise identical — the streamed band's forward bitwise
    equality is the whole story, because with a frozen trunk the
    correlation is off the differentiation path and the custom VJP never
    runs."""
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    cfg_d = _cfg(nc_topk=5)
    cfg_s = cfg_d.replace(corr_impl="stream", corr_stream_tile=6)
    optimizer = make_optimizer()
    rng = np.random.RandomState(21)
    batches = [
        {
            "source_features": rng.randn(2, 3, 4, 256).astype(np.float32),
            "target_features": rng.randn(2, 3, 4, 256).astype(np.float32),
        }
        for _ in range(3)
    ]

    def run(cfg):
        params = init_immatchnet(jax.random.PRNGKey(0), cfg)
        state = create_train_state(params, optimizer)
        step = make_train_step(
            cfg, optimizer, donate=False, from_features=True
        )
        losses = []
        for batch in batches:
            state, loss = step(state, batch)
            losses.append(np.asarray(loss))
        return losses, state.params

    losses_d, params_d = run(cfg_d)
    losses_s, params_s = run(cfg_s)
    for ld, ls in zip(losses_d, losses_s):
        _assert_bitwise(ls, ld)
    flat_d = jax.tree_util.tree_leaves_with_path(params_d)
    flat_s = jax.tree_util.tree_leaves_with_path(params_s)
    assert len(flat_d) == len(flat_s)
    for (pd, ld), (ps, ls) in zip(flat_d, flat_s):
        assert pd == ps
        _assert_bitwise(ls, ld)


# ----------------------------------------------------------------------
# serving: impl flip at zero recompiles, bitwise-identical responses


def test_serve_stream_variant_flip_zero_recompiles():
    """An engine whose degraded rung is the SAME band served via the
    streamed correlation: flipping the controller between the two impls
    never retraces (both variants pre-warm) and — because the band is
    bitwise — never changes a bit of the response either."""
    from ncnet_tpu.serve import ServeEngine, make_serve_match_step, payload_spec

    cfg_d = _cfg(nc_topk=3)
    cfg_s = cfg_d.replace(corr_impl="stream", corr_stream_tile=3)
    params = init_immatchnet(jax.random.PRNGKey(0), cfg_d)

    class PinnedDegrade:
        degraded = False

        def update(self, pressure):
            return self.degraded

    ctrl = PinnedDegrade()
    rng = np.random.RandomState(5)
    payload = {
        "source_image": rng.rand(32, 48, 3).astype(np.float32),
        "target_image": rng.rand(48, 32, 3).astype(np.float32),
    }
    key = (payload["source_image"].shape, payload["target_image"].shape)
    with ServeEngine(
        make_serve_match_step(cfg_d),
        params,
        max_batch=1,
        max_wait=0.005,
        batch_sizes=(1,),
        degraded_apply_fn=make_serve_match_step(cfg_s),
        degrade_controller=ctrl,
    ) as eng:
        eng.warmup([(key, payload_spec(payload))])
        warm_traces = eng.compile_count
        outs = []
        for flag in (False, True, False):
            ctrl.degraded = flag
            fut = eng.submit(key=key, payload=payload)
            outs.append(np.asarray(fut.result(timeout=120)["matches"]))
        stats = eng.report()
    assert eng.compile_count == warm_traces
    assert stats["recompiles_after_warmup"] == 0
    _assert_bitwise(outs[1], outs[0])  # stream rung == dense rung, bitwise
    _assert_bitwise(outs[2], outs[0])


# ----------------------------------------------------------------------
# the audit proof: FLOP ledger exact, memory highwater gated


def test_corr_stream_flop_forms():
    from ncnet_tpu.ops.accounting import (
        corr_select_flops,
        train_step_flops_for_batch,
    )

    dense = corr_select_flops(2, 12, 12, 16)
    assert dense == 2 * 2.0 * 12 * 12 * 16
    # dividing tile: streamed FLOPs are EXACTLY dense — the win is
    # memory/bandwidth, never arithmetic
    assert corr_select_flops(2, 12, 12, 16, "stream", corr_tile=4) == dense
    # non-dividing tile: the padded tail columns, exactly
    assert corr_select_flops(
        2, 12, 10, 16, "stream", corr_tile=4
    ) == 2 * 2.0 * 12 * 12 * 16
    # tile clamps to nb like resolve_corr_tile
    assert corr_select_flops(
        1, 4, 4, 8, "stream", corr_tile=128
    ) == corr_select_flops(1, 4, 4, 8)
    with pytest.raises(ValueError):
        corr_select_flops(1, 4, 4, 8, "stream", corr_tile=0)
    with pytest.raises(ValueError):
        corr_select_flops(1, 4, 4, 8, "tiled")
    # the config-driven dispatcher: stream config with the (clamped)
    # default tile == the dense count for the same geometry
    batch = {
        "source_features": np.zeros((2, 4, 4, 256), np.float32),
        "target_features": np.zeros((2, 4, 4, 256), np.float32),
    }
    assert train_step_flops_for_batch(
        _cfg(nc_topk=4, corr_impl="stream"), batch
    ) == train_step_flops_for_batch(_cfg(nc_topk=4), batch)


def test_corr_stream_audit_memory_gate():
    """The tentpole's acceptance gate: at the audit's dedicated corr
    geometry the streamed program's jaxpr memory highwater is <= 0.35x
    the dense program's, and both walk exactly their closed forms."""
    from ncnet_tpu.analysis import jaxpr_audit as ja
    from ncnet_tpu.analysis.hlo_audit import jaxpr_memory_highwater

    measured = {}
    for name in ("corr/dense", "corr/stream"):
        built = ja.PROGRAMS[name].build()
        jaxpr = jax.make_jaxpr(built.fn)(*built.args)
        assert ja.jaxpr_flops(jaxpr.jaxpr) == built.expected_flops
        measured[name] = jaxpr_memory_highwater(jaxpr.jaxpr)
    ratio = measured["corr/stream"] / measured["corr/dense"]
    assert ratio <= 0.35, (
        f"stream highwater {measured['corr/stream']} vs dense "
        f"{measured['corr/dense']}: ratio {ratio:.3f} > 0.35"
    )
