"""Mesh helpers: shapes, hybrid single-process reduction, multihost no-op."""

import numpy as np

import jax

from ncnet_tpu.parallel.mesh import (
    initialize_multihost,
    make_hybrid_mesh,
    make_mesh,
    replicate,
    shard_batch,
)


def test_make_mesh_2d():
    mesh = make_mesh((2, 4), ("data", "spatial"))
    assert mesh.shape == {"data": 2, "spatial": 4}


def test_hybrid_mesh_single_process_reduces_to_make_mesh():
    mesh = make_hybrid_mesh()
    assert mesh.shape == {"data": len(jax.devices())}


def test_initialize_multihost_single_process_noop():
    pid, n = initialize_multihost()
    assert (pid, n) == (0, 1)


def test_initialize_multihost_require_env_hard_fails(monkeypatch):
    # NCNET_REQUIRE_MULTIHOST turns the silent single-host fallback into
    # an error: on a real pod a broken auto-detection would otherwise
    # leave every host training its own divergent model.
    import pytest

    monkeypatch.setenv("NCNET_REQUIRE_MULTIHOST", "4")
    with pytest.raises(RuntimeError, match="NCNET_REQUIRE_MULTIHOST"):
        initialize_multihost()
    # non-numeric truthy value requires merely >1 process
    monkeypatch.setenv("NCNET_REQUIRE_MULTIHOST", "yes")
    with pytest.raises(RuntimeError, match="NCNET_REQUIRE_MULTIHOST"):
        initialize_multihost()
    # '1' means "guard enabled" (boolean convention), not "1 process ok"
    monkeypatch.setenv("NCNET_REQUIRE_MULTIHOST", "1")
    with pytest.raises(RuntimeError, match="NCNET_REQUIRE_MULTIHOST"):
        initialize_multihost()
    # '0' disables like unset
    monkeypatch.setenv("NCNET_REQUIRE_MULTIHOST", "0")
    assert initialize_multihost() == (0, 1)
    # unset -> single-host fallback stays a no-op
    monkeypatch.delenv("NCNET_REQUIRE_MULTIHOST")
    assert initialize_multihost() == (0, 1)


def test_shard_and_replicate_roundtrip():
    mesh = make_mesh()
    batch = {"x": np.arange(16, dtype=np.float32).reshape(8, 2)}
    sharded = shard_batch(mesh, batch)
    np.testing.assert_array_equal(np.asarray(sharded["x"]), batch["x"])
    params = {"w": np.ones((3,), np.float32)}
    rep = replicate(mesh, params)
    np.testing.assert_array_equal(np.asarray(rep["w"]), params["w"])
