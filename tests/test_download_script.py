"""No-network smoke test of datasets/download.sh control flow.

Round-2 finding: the inloc branch did ``cd inloc`` under ``set -e`` with no
datasets/inloc directory in the repo, so the script died before its wget
lines. This test runs every branch in a sandbox with stubbed network tools
and asserts each branch actually reaches its fetch commands.
"""

import shutil
import stat
import subprocess
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

STUB = """#!/bin/bash
echo "$0 $@" >> "$STUB_LOG"
exit 0
"""


def _sandbox(tmp_path):
    ds = tmp_path / "datasets"
    ds.mkdir()
    shutil.copy(REPO / "datasets" / "download.sh", ds / "download.sh")
    (ds / "pf-pascal").mkdir()
    ivd = ds / "ivd"
    ivd.mkdir()
    (ivd / "dirs.txt").write_text("a/b/c venue\nd/e/f venue\n")
    (ivd / "urls.txt").write_text(
        "a/b/c/img1.jpg https://example.invalid/img1\n"
        "d/e/f/img2.jpg https://example.invalid/img2\n"
    )
    # note: no inloc/ directory — the script must create it itself
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    for tool in ("wget", "unzip"):
        p = bin_dir / tool
        p.write_text(STUB)
        p.chmod(p.stat().st_mode | stat.S_IXUSR)
    return ds, bin_dir


def _run(ds, bin_dir, tmp_path, arg):
    log = tmp_path / f"log_{arg.replace('-', '_')}"
    log.write_text("")
    env = {
        "PATH": f"{bin_dir}:/usr/bin:/bin",
        "STUB_LOG": str(log),
    }
    proc = subprocess.run(
        ["bash", str(ds / "download.sh"), arg],
        env=env,
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, (
        f"{arg}: rc={proc.returncode}\nstdout={proc.stdout}\n"
        f"stderr={proc.stderr}"
    )
    return log.read_text()


def test_every_branch_reaches_its_fetch_lines(tmp_path):
    ds, bin_dir = _sandbox(tmp_path)
    log = _run(ds, bin_dir, tmp_path, "all")
    assert "PF-dataset-PASCAL.zip" in log, "pf-pascal wget not reached"
    assert "unzip" in log
    assert "img1" in log and "img2" in log, "ivd wget not reached"
    assert "cutouts.tar.gz" in log and "iphone7.tar.gz" in log, (
        "inloc wget not reached"
    )
    assert (ds / "inloc").is_dir(), "inloc dir not created"
    # ivd venue dirs are created before the downloads
    assert (ds / "ivd" / "a" / "b" / "c").is_dir()


def test_individual_branches(tmp_path):
    ds, bin_dir = _sandbox(tmp_path)
    log = _run(ds, bin_dir, tmp_path, "inloc")
    assert "cutouts.tar.gz" in log
    assert "PF-dataset-PASCAL" not in log
    log = _run(ds, bin_dir, tmp_path, "pf-pascal")
    assert "PF-dataset-PASCAL.zip" in log
    assert "cutouts" not in log
    log = _run(ds, bin_dir, tmp_path, "ivd")
    assert "img1" in log
